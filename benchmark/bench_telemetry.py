"""Paired plane-off-vs-plane-on serving run — the fleet-telemetry
acceptance benchmark.

The telemetry plane's contract is "observation never perturbs the
serving path": arming `ClusterConfig.telemetry_interval_s` may add
host-side snapshot/encode work per cadence tick, but it must not
change a single token and must stay cheap.  Both halves are gated
here over the same seeded virtual-clock cluster trace:

- **Exact token parity** — the ON run's per-request token streams
  byte-compare equal to the OFF run's (``telemetry_token_parity``).
  This is exactness, not a latency measurement, so it gates hard.
- **Bounded overhead** — min-of-N wall time with the plane armed is
  within 10% of plane-off (mirrored run order, min-of-N: the plane
  is dict snapshots plus delta encoding on a cadence, so more than
  that is a hot-path regression, not noise).

Emitted rows (one JSON line each, ``bench: "telemetry"``): one row
per mode with its wall time, then the paired summary
``check_bench_regression.telemetry_checks`` gates.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # repo root

import argparse
import json
import time

import jax

from triton_distributed_tpu.serving import (
    ClusterConfig,
    SchedulerConfig,
    ServingCluster,
    ToyConfig,
    ToyModel,
)

#: Enough requests that the plane ticks through several cadences and
#: at least one keyframe cycle on the virtual clock.
N_REQUESTS = 10
N_RUNS = 3


def _trace():
    gens = [6, 9, 7, 11, 6, 8, 10, 7, 9, 6][:N_REQUESTS]
    return [dict(prompt=[1 + i, 2 + (i % 3), 3, 4, 5 + (i % 2)],
                 max_new_tokens=g, seed=100 + i,
                 arrival_time=0.002 * (i % 4))
            for i, g in enumerate(gens)]


def _run(toy, telemetry_interval_s):
    """One full cluster trace; returns (tokens, wall_s, fleet)."""
    model, params = toy
    sc = SchedulerConfig(num_slots=3, prefill_buckets=(8, 16, 32),
                         temperature=0.8, top_k=8)
    cluster = ServingCluster(
        model, params,
        ClusterConfig(n_replicas=2, scheduler=sc,
                      telemetry_interval_s=telemetry_interval_s))
    t0 = time.perf_counter()
    for t in _trace():
        cluster.submit(**t)
    done = cluster.drain()
    wall = time.perf_counter() - t0
    tokens = [r.tokens for r in sorted(done,
                                       key=lambda r: r.record_id)]
    return tokens, wall, cluster.fleet


def sweep(out):
    rows = []

    def emit(rec):
        rows.append(rec)
        line = json.dumps(rec)
        print(line)
        if out is not None:
            out.write(line + "\n")

    model = ToyModel(ToyConfig(vocab_size=61, hidden=16,
                               max_seq_len=64))
    params = model.init_params(jax.random.key(0))
    toy = (model, params)

    # Warm the jit caches off the books so neither mode pays
    # first-compile inside its measurement.
    _run(toy, None)

    off_s, on_s = [], []
    tokens_off = tokens_on = None
    frames = sources = alerts = 0
    for i in range(N_RUNS):
        # Mirrored order so drift (thermal, page cache) cancels.
        order = (("off", "on") if i % 2 == 0 else ("on", "off"))
        for mode in order:
            if mode == "off":
                tokens_off, wall, _ = _run(toy, None)
                off_s.append(wall)
            else:
                tokens_on, wall, fleet = _run(toy, 0.25)
                on_s.append(wall)
                frames = fleet.collector.folded
                sources = len(fleet.collector.sources())
                alerts = len(fleet.engine.events)

    for mode, walls in (("off", off_s), ("on", on_s)):
        emit({"bench": "telemetry", "workload": "paired_trace",
              "mode": mode, "n_requests": N_REQUESTS,
              "s": round(min(walls), 4),
              "samples_s": [round(w, 4) for w in walls]})

    overhead = min(on_s) / min(off_s) - 1.0
    emit({"bench": "telemetry", "workload": "paired_trace",
          "mode": "paired", "n_requests": N_REQUESTS,
          "telemetry_off_s": round(min(off_s), 4),
          "telemetry_on_s": round(min(on_s), 4),
          "telemetry_overhead": round(overhead, 4),
          "telemetry_overhead_le_10pct": overhead <= 0.10,
          "telemetry_token_parity": tokens_on == tokens_off,
          "frames_published": frames,
          "telemetry_sources": sources,
          "telemetry_alerts_fired": alerts})
    return rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None,
                    help="also append the JSON lines here (the "
                         "committed copy lives at "
                         "benchmark/results/telemetry.json)")
    args = ap.parse_args()
    out = open(args.out, "w") if args.out else None
    rows = sweep(out)
    if out is not None:
        out.close()
    paired = [r for r in rows if r.get("mode") == "paired"]
    assert all(r["telemetry_token_parity"] for r in paired), paired
    assert all(r["frames_published"] > 0 for r in paired), paired
    return 0


if __name__ == "__main__":
    sys.exit(main())
