"""W8A8 int8 matmul vs the XLA int8 dot and the bf16 MXU peak.

On v5e the int8 MXU path doubles peak throughput (394 TOPS vs
197 TFLOP/s bf16).  Emits one JSON line per shape; `tops` counts the
int multiply-accumulates (the dequant epilogue is O(m·n) extra).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # repo root

import argparse
import functools
import json

import jax
import jax.numpy as jnp

from triton_distributed_tpu.kernels.quantized import matmul_w8a8
from triton_distributed_tpu.utils.benchmarking import measure_ops_scanned


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--shapes", nargs="*",
                    default=["4096,4096,4096", "4096,7168,7168"])
    ap.add_argument("--repeats", type=int, default=4)
    args = ap.parse_args()

    for spec in args.shapes:
        m, k, n = (int(x) for x in spec.split(","))
        a = jax.random.randint(jax.random.key(0), (m, k), -127, 127,
                               jnp.int8)
        b = jax.random.randint(jax.random.key(1), (k, n), -127, 127,
                               jnp.int8)
        sa = jnp.full((m,), 1e-2, jnp.float32)
        sb = jnp.full((n,), 1e-2, jnp.float32)

        ours = functools.partial(matmul_w8a8, out_dtype=jnp.bfloat16)

        def xla_int8(a_, b_, sa_, sb_):
            acc = jax.lax.dot_general(
                a_, b_, dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
            return (acc.astype(jnp.float32) * sa_[:, None] * sb_[None, :]
                    ).astype(jnp.bfloat16)

        # Chain: fold the bf16 output back into the int8 activations
        # (crop/pad so any M, K, N relation works).
        def mix(ar, out):
            crop = out[:, :min(k, n)].astype(jnp.int32) * 8
            crop = jnp.pad(crop, ((0, 0), (0, k - crop.shape[1])))
            nxt = ar[0].astype(jnp.int32) + crop
            return (jnp.clip(nxt, -127, 127).astype(jnp.int8),) + ar[1:]

        t_ours, t_base = measure_ops_scanned(
            [ours, xla_int8], (a, b, sa, sb), mix, n_inner=8,
            repeats=args.repeats)
        ops = 2 * m * k * n
        print(json.dumps({
            "bench": "int8_gemm", "M": m, "K": k, "N": n,
            "us": round(t_ours * 1e6, 1),
            "tops": round(ops / t_ours / 1e12, 1),
            "vs_baseline": round(t_base / t_ours, 3),
        }), flush=True)


if __name__ == "__main__":
    main()
