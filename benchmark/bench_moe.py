"""Fused MoE pipelines on hardware (VERDICT r4 missing #2 / weak #3):

- `moe_reduce_rs_fused` (grouped down-GEMM + one-hot combine in ONE
  kernel) vs the staged composition (Pallas grouped GEMM → XLA
  combine) and pure XLA — measurable at world=1, where the kernel is
  the chunk pipeline + combine matmul with no RS stage.
- `ag_group_gemm` (fused AG + grouped GEMM; world=1 = the in-kernel
  grouped pipeline) vs XLA.
- int8: `grouped_matmul_w8a8` vs bf16 `grouped_matmul` at the
  weight-streaming-bound MoE decode shape (E=64, cap=128) — expert
  weights at half the bytes double the binding roofline — and the
  quantized fused epilogue vs its bf16 twin.

Reference analogue: the MoE layer/e2e bench recipes
(`docs/e2e.md:30-123`) and the published a2a dispatch latency
(`README.md:96-97`).

ABBA bracketing + per-repeat paired ratios + spread fields, like
`bench_attention.py`.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # repo root

import argparse
import statistics

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from triton_distributed_tpu.kernels import moe_utils
from triton_distributed_tpu.kernels.allgather_group_gemm import (
    AGGroupGEMMContext,
    ag_group_gemm,
)
from triton_distributed_tpu.kernels.grouped_gemm import (
    grouped_matmul,
    grouped_matmul_w8a8,
)
from triton_distributed_tpu.kernels.moe_reduce_rs import (
    MoEReduceRSContext,
    moe_reduce_rs_fused,
)
from triton_distributed_tpu.kernels.quantized import quantize_sym
from triton_distributed_tpu.observability import bench_record
from triton_distributed_tpu.ops import shard_map_op
from triton_distributed_tpu.utils.benchmarking import (
    feedback_mix,
    measure_ops_scanned,
)


def _emit(row):
    # Through the metrics registry: stdout, benchmark/results/moe.json
    # and the rolling anomaly baselines all carry the same record.
    bench_record(row)


def _paired_stats(slopes, self_first, self_last):
    """slopes rows: [ours, *baselines, ours]; per-repeat pairing."""
    ours_pairs = [(x + y) / 2 for x, y in zip(slopes[self_first],
                                              slopes[self_last])]
    t_self = statistics.median(slopes[self_first] + slopes[self_last])

    def ratio(idx):
        rs = sorted(t / o for t, o in zip(slopes[idx], ours_pairs))
        return (round(statistics.median(rs), 3),
                [round(rs[0], 3), round(rs[-1], 3)])

    return t_self, ratio


def bench_moe_epilogue(e, cap, mc, k, n, topk, repeats):
    """moe_reduce_rs_fused (packed combine-in-epilogue) vs staged
    (Pallas grouped GEMM → XLA gather combine) vs pure XLA at world=1.

    Both baselines use the gather-based `combine_tokens` — the
    strongest XLA combine (topk gathers, no dense one-hot matmul), so
    `vs_xla` measures the fused epilogue against what a user would
    actually run, not a strawman."""
    mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
    key = jax.random.key(0)
    buckets = (jax.random.normal(key, (1, e, cap, k)) / 8
               ).astype(jnp.bfloat16)
    wdown = (jax.random.normal(jax.random.fold_in(key, 1), (e, k, n))
             / 8).astype(jnp.bfloat16)
    ids = jax.random.randint(jax.random.fold_in(key, 2), (mc, topk),
                             0, e)
    tw = jax.nn.softmax(jax.random.normal(
        jax.random.fold_in(key, 3), (mc, topk)), axis=-1)
    plan = moe_utils.plan_chunks(ids, tw, 1, e, cap,
                                 dtype=jnp.bfloat16)
    cmatb = plan.combine_blocks
    occupancy = int(plan.n_blocks[0]) * plan.pack_block_size

    ctx = MoEReduceRSContext(axis="tp", world_size=1, num_experts=e,
                             topk=topk)

    def fused(bk, w_, cm):
        return shard_map_op(
            lambda b_, ww, c_: moe_reduce_rs_fused(
                b_, ww, plan._replace(combine_blocks=c_), ctx),
            mesh, in_specs=(P(), P(), P()), out_specs=P())(bk, w_, cm)

    def staged(bk, w_, cm):
        part = grouped_matmul(bk[0], w_)              # (E, cap, n)
        return moe_utils.combine_tokens(part, ids, plan.slot_of_pair[0],
                                        tw)

    def xla(bk, w_, cm):
        part = jnp.einsum("eck,ekn->ecn", bk[0], w_,
                          preferred_element_type=jnp.float32
                          ).astype(bk.dtype)
        return moe_utils.combine_tokens(part, ids, plan.slot_of_pair[0],
                                        tw)

    # chain through buckets (feed the (mc, n) output back into the
    # bucket tensor so iterations are data-dependent); identical mix
    # cost for every op in the ABBA set, so ratios are unbiased
    def mix(a, out):
        return (feedback_mix(a[0], out[None, None]), a[1], a[2])

    ops = [fused, staged, xla, fused]
    _, slopes = measure_ops_scanned(
        ops, (buckets, wdown, cmatb), mix,
        n_inner=16, repeats=repeats, return_slopes=True)
    t_fused, ratio = _paired_stats(slopes, 0, -1)
    flops = 2 * e * cap * k * n + 2 * e * mc * cap * n
    vs_staged, staged_rng = ratio(1)
    vs_xla, xla_rng = ratio(2)
    _emit({
        "bench": "moe_reduce_rs_fused", "world": 1,
        "E": e, "cap": cap, "mc": mc, "K": k, "N": n,
        "note": "degenerate_world1_no_rs_stage",
        "us": round(t_fused * 1e6, 1),
        "tflops": round(flops / t_fused / 1e12, 1),
        "pack_block": plan.pack_block_size,
        "packed_rows": occupancy, "dense_rows": e * cap,
        "vs_staged": vs_staged, "vs_staged_range": staged_rng,
        "vs_xla": vs_xla, "vs_xla_range": xla_rng,
    })


def bench_ag_group_gemm(e, cap, k, n, repeats):
    """ag_group_gemm at world=1 (in-kernel grouped pipeline) vs XLA."""
    mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
    key = jax.random.key(1)
    buckets = (jax.random.normal(key, (e, cap, k)) / 8
               ).astype(jnp.bfloat16)
    w = (jax.random.normal(jax.random.fold_in(key, 1), (e, k, n)) / 8
         ).astype(jnp.bfloat16)
    ctx = AGGroupGEMMContext(axis="tp", world_size=1, num_experts=e)

    def fused(bk, ww):
        out = shard_map_op(
            lambda b_, w_: ag_group_gemm(b_, w_, ctx),
            mesh, in_specs=(P(), P()), out_specs=P())(bk, ww)
        return out[0]                                  # (E, cap, n)

    def xla(bk, ww):
        return jnp.einsum("eck,ekn->ecn", bk, ww,
                          preferred_element_type=jnp.float32
                          ).astype(bk.dtype)

    def mix(a, out):
        return (feedback_mix(a[0], out), a[1])

    ops = [fused, xla, fused]
    _, slopes = measure_ops_scanned(
        ops, (buckets, w), mix, n_inner=16, repeats=repeats,
        return_slopes=True)
    t_fused, ratio = _paired_stats(slopes, 0, -1)
    vs_xla, rng = ratio(1)
    flops = 2 * e * cap * k * n
    _emit({
        "bench": "ag_group_gemm", "world": 1,
        "E": e, "cap": cap, "K": k, "N": n,
        "note": "degenerate_world1_overhead_only",
        "us": round(t_fused * 1e6, 1),
        "tflops": round(flops / t_fused / 1e12, 1),
        "vs_xla": vs_xla, "vs_xla_range": rng,
    })


def bench_grouped_w8a8(e, cap, k, n, repeats):
    """int8 grouped GEMM vs bf16 at the weight-bound MoE shape."""
    key = jax.random.key(2)
    a = (jax.random.normal(key, (e, cap, k)) / 8).astype(jnp.bfloat16)
    b = (jax.random.normal(jax.random.fold_in(key, 1), (e, k, n)) / 8
         ).astype(jnp.bfloat16)
    a_q, sa = quantize_sym(a, axis=2)
    b_q, sb = quantize_sym(b, axis=1)

    def int8(aq, af, saq, bq, sbq, bf_b):
        return grouped_matmul_w8a8(aq, bq, saq, sbq)

    def bf16(aq, af, saq, bq, sbq, bf_b):
        return grouped_matmul(af, bf_b)

    # Chain BOTH activation tensors on every iteration (the ops read
    # different ones; an unchained operand would let XLA hoist the
    # whole matmul out of the scan).  The mix cost is identical for
    # both ops, so the paired ratio stays unbiased.
    def mix(a_, out):
        return (feedback_mix(a_[0], out), feedback_mix(a_[1], out),
                *a_[2:])

    ops = [int8, bf16, int8]
    _, slopes = measure_ops_scanned(
        ops, (a_q, a, sa, b_q, sb, b), mix, n_inner=16,
        repeats=repeats, carry_args=2, return_slopes=True)
    t_int8, ratio = _paired_stats(slopes, 0, -1)
    speedup, rng = ratio(1)
    flops = 2 * e * cap * k * n
    _emit({
        "bench": "grouped_gemm_w8a8", "E": e, "cap": cap, "K": k, "N": n,
        "us": round(t_int8 * 1e6, 1),
        "tops": round(flops / t_int8 / 1e12, 1),
        "speedup_vs_bf16": speedup, "speedup_range": rng,
    })


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--repeats", type=int, default=4)
    args = ap.parse_args()

    # weight-streaming-bound decode shape (docs/performance.md) and a
    # compute-bound prefill shape
    bench_grouped_w8a8(64, 128, 2048, 1408, args.repeats)
    bench_grouped_w8a8(8, 1024, 7168, 2048, args.repeats)
    bench_ag_group_gemm(64, 128, 2048, 1408, args.repeats)
    bench_ag_group_gemm(8, 512, 2048, 1408, args.repeats)
    bench_moe_epilogue(64, 128, 2048, 2048, 1408, 2, args.repeats)
    bench_moe_epilogue(8, 512, 2048, 2048, 1408, 2, args.repeats)


if __name__ == "__main__":
    main()
