"""Low-latency EP AllToAll sweep vs `jax.lax.all_to_all`.

The reference's headline op (137 µs dispatch @ 32 ranks, 128 tok/rank,
hidden 7168 — BASELINE.md).  Emits one JSON line per capacity.
Meaningful on >1 device.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # repo root

import argparse
import json

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from triton_distributed_tpu.kernels.low_latency_all_to_all import (
    AllToAllContext,
    fast_all_to_all,
)
from triton_distributed_tpu.ops import shard_map_op
from triton_distributed_tpu.utils.benchmarking import measure_ops


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--caps", type=int, nargs="*", default=[16, 128, 512])
    ap.add_argument("--hidden", type=int, default=7168)
    ap.add_argument("--repeats", type=int, default=4)
    args = ap.parse_args()

    devices = jax.devices()
    world = len(devices)
    mesh = Mesh(np.array(devices), ("ep",))

    for cap in args.caps:
        send = jax.random.normal(
            jax.random.key(0), (world, world, cap, args.hidden)
        ).astype(jnp.bfloat16)
        counts = jnp.full((world, world, 1), cap, jnp.int32)

        ctx = AllToAllContext(axis="ep", world_size=world,
                              max_tokens_per_rank=cap,
                              hidden=args.hidden)
        fused = jax.jit(shard_map_op(
            lambda s, c: fast_all_to_all(s[0], c[0], ctx)[0][None],
            mesh, in_specs=(P("ep", None, None, None), P("ep", None, None)),
            out_specs=P("ep", None, None, None)))

        def xla_impl(s, c):
            del c
            return jax.lax.all_to_all(s[0], "ep", split_axis=0,
                                      concat_axis=0, tiled=False)[None]

        base = jax.jit(shard_map_op(
            xla_impl, mesh,
            in_specs=(P("ep", None, None, None), P("ep", None, None)),
            out_specs=P("ep", None, None, None)))

        # Jitted chain: eager ops pay ~5 ms dispatch via the tunnel.
        mix = jax.jit(lambda out, s: out * jnp.bfloat16(0.5)
                      + s * jnp.bfloat16(0.5))
        chain = lambda a, out: (mix(out, a[0]), a[1])
        t_fused, t_base = measure_ops([fused, base], (send, counts),
                                      chain, repeats=args.repeats)
        print(json.dumps({
            "bench": "all_to_all", "world": world, "cap": cap,
            "hidden": args.hidden, "us": round(t_fused * 1e6, 1),
            "vs_baseline": round(t_base / t_fused, 3),
            # Self-describing degeneracy (VERDICT r3 weak #6): at
            # world=1 both sides shuffle nothing — overhead only.
            "degenerate_world1_overhead_only": world <= 1,
        }), flush=True)


if __name__ == "__main__":
    main()
