"""Router bench: cluster scale-out vs the single engine, and
signal-aware vs round-robin placement under seeded replica imbalance
— the ISSUE-9 acceptance benchmark.

A *virtual-time* benchmark, deliberately: the scenario the router
pays off in — one replica of a data-parallel pod running slow (hot
ICI links, thermal throttle, a noisy neighbor) while the others are
fine — cannot be produced on a CPU CI host reproducibly.  So the
imbalance is SEEDED: every replica/worker runs on the shared virtual
clock with a modeled per-step cost (`ClusterConfig.step_time_s`; a
straggling replica's steps cost ``factor``×, a link-contended one
``1/(1-busy)``× — the same residual-bandwidth ground truth the
closed-loop bench uses), the REAL schedulers decode the REAL toy
model underneath, and makespan/TTFT are read off the virtual clock —
deterministic given the trace, machine-independent.

Emitted rows (one JSON line each, ``bench: "router"``):

- ``workload: "scale"`` — router + N replicas vs N=1 on the same
  trace: virtual makespan (``ms``), mean/p99 TTFT, useful-token
  throughput, ``speedup_vs_single``;
- ``workload: "disagg"`` — 2 replicas + 1 prefill worker: the same
  metrics plus shipped-KV accounting;
- ``workload: "imbalance_*"`` — per (mode ∈ round_robin /
  signal_aware) rows and one ``mode: "paired"`` summary with
  ``signal_aware_beats_rr`` (the gate: placement signals must WIN
  under seeded imbalance);
- ``workload: "balanced"`` — the paired summary carries
  ``matches_round_robin`` (identical assignments — balanced signals
  reproduce the rotation exactly) and ``signal_aware_never_worse``;
- ``workload: "kvtier_fleet"`` — the SHARED-PREFIX fleet trace (KV
  tier, ISSUE 15): one hot system prompt served through 1/2/4 paged
  replicas with peer prefix shipping armed (seeded prefill baseline
  + synthetic bus, so the ship-vs-recompute model engages
  deterministically).  Each row carries the fleet-wide prefill work
  in tokens (``fleet_prefill_tokens`` = prefix-cache miss tokens
  summed over every replica), ``prefix_ships``,
  ``zero_second_prefill`` (the shared prefix was full-prefilled
  exactly ONCE across the whole fleet — replicas B..N served it
  from the peer tier), ``fleet_prefill_sublinear``
  (work(n) < n × work(1)) and ``prefix_ship_exact`` (token-for-token
  vs the single-engine scheduler); the n=2 row also pairs against a
  ship-disabled run (``ship_beats_recompute``: strictly fewer
  prefill tokens with shipping on).

Gate semantics (`scripts/check_bench_regression.py`
``router_checks`` + ``kvtier_checks``): every fresh imbalance pair
must report ``signal_aware_beats_rr``, every balanced pair
``matches_round_robin`` + ``signal_aware_never_worse``, and every
kvtier_fleet row must hold all four KV-tier booleans.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # repo root

import argparse
import json

import jax
import numpy as np

from triton_distributed_tpu.serving import (
    ClusterConfig,
    SchedulerConfig,
    ServingCluster,
    ToyConfig,
    ToyModel,
)
from triton_distributed_tpu.serving.cluster import RouterConfig

#: Modeled virtual costs (fixed so committed numbers are
#: machine-independent; the v5e-ish 1 ms decode step of the serving
#: bench's 24-slot toy configuration).
STEP_S = 1e-3
PREFILL_S = 2e-3

N_REQUESTS = 24
SLOTS = 4
BUCKETS = (8, 16, 32)


def build_trace(homogeneous: bool = False):
    """Seeded arrival trace.  The heterogeneous trace (varied prompt
    lengths / budgets, exponential interarrivals) drives the scale
    and imbalance sweeps; the homogeneous one (identical requests,
    uniform spacing) is the balanced-parity fixture — symmetric load
    is what makes 'signal-aware == round-robin' exact."""
    rng = np.random.default_rng(1234)
    trace = []
    t = 0.0
    for i in range(N_REQUESTS):
        if homogeneous:
            t += 0.0015
            prompt = [1 + (i % 7), 2, 3, 4, 5, 6]
            gen = 8
        else:
            t += float(rng.exponential(0.0005))
            plen = int(rng.integers(4, 14))
            prompt = [int(x) for x in rng.integers(1, 61, plen)]
            gen = int(rng.integers(5, 13))
        trace.append(dict(prompt=prompt, max_new_tokens=gen,
                          seed=1000 + i, arrival_time=round(t, 6)))
    return trace


def hop_breakdowns(done):
    """Per-hop p50/p99 TTFT decomposition over the finished records
    (`observability.lineage.ttft_breakdown`), plus the hop-sum ≡ TTFT
    exactness flag the regression gate enforces on every row."""
    from triton_distributed_tpu.observability.audit import percentile
    from triton_distributed_tpu.observability.lineage import (
        get_lineage_recorder, ttft_breakdown)
    rec = get_lineage_recorder()
    per_hop = {}
    exact = True
    for r in done:
        bd = ttft_breakdown(rec.events_for(r.record_id),
                            arrival=r.arrival_time,
                            measured_ttft=r.ttft)
        exact = exact and bd is not None and bd["exact"]
        if bd is not None:
            for hop, ms in bd["by_hop_ms"].items():
                per_hop.setdefault(hop, []).append(ms)
    return {
        "hop_p50_ms": {h: round(percentile(v, 50), 6)
                       for h, v in sorted(per_hop.items())},
        "hop_p99_ms": {h: round(percentile(v, 99), 6)
                       for h, v in sorted(per_hop.items())},
        "hop_sum_exact": exact,
    }


def run_cluster(model, params, trace, n_replicas, mode,
                workers=0, straggle=None, link_busy=None):
    from triton_distributed_tpu.observability.lineage import (
        get_lineage_recorder)
    get_lineage_recorder().clear()
    cfg = ClusterConfig(
        n_replicas=n_replicas, n_prefill_workers=workers,
        scheduler=SchedulerConfig(num_slots=SLOTS,
                                  prefill_buckets=BUCKETS),
        router=RouterConfig(mode=mode),
        step_time_s=STEP_S, prefill_time_s=PREFILL_S)
    cluster = ServingCluster(model, params, cfg)
    if straggle:
        idx, factor = straggle
        cluster.straggle_replica(idx, factor)
        # Ground truth AND signal agree from t=0: the replica already
        # knows its step cost (a deployment's rolling step baseline).
        cluster.replicas[idx].last_step_s = STEP_S * factor
    if link_busy:
        idx, busy = link_busy
        cluster.replicas[idx].link_busy = busy
        # Ground truth: a contended link slows every decode step to
        # the residual-bandwidth share (the feedback.effective_spec
        # model applied to the step time).
        cluster.straggle_replica(idx, 1.0 / (1.0 - busy))
    recs = [cluster.submit(**t) for t in trace]
    done = cluster.drain()
    assert len(done) == len(trace), [r.state for r in recs]
    tokens = sum(len(r.tokens) for r in done)
    makespan = (max(r.t_finish for r in done)
                - min(r.arrival_time for r in done))
    ttfts = sorted(r.ttft for r in done)
    hops = hop_breakdowns(done)
    assert hops["hop_sum_exact"], (
        "TTFT hop decomposition drifted from the measured TTFT")
    return {
        "ms": round(makespan * 1e3, 6),
        "mean_ttft_ms": round(1e3 * sum(ttfts) / len(ttfts), 6),
        "p99_ttft_ms": round(1e3 * ttfts[
            min(len(ttfts) - 1, int(0.99 * len(ttfts)))], 6),
        "useful_tokens": tokens,
        "tokens_per_virtual_s": round(tokens / makespan, 3),
        "assignments": [tuple(r.replica_history) for r in recs],
        "streams": [r.tokens for r in
                    sorted(done, key=lambda r: r.record_id)],
        "kv_shipped_bytes": cluster.transport.shipped_bytes,
        "shipments": cluster.transport.shipments,
        "failovers": len(cluster.router.failovers),
        **hops,
    }


def kvtier_fleet_rows(model, params):
    """The shared-prefix fleet sweep: fleet-wide prefill work must be
    SUB-LINEAR in replica count because a prefix prefilled on replica
    A serves every other replica through the peer tier with zero
    second prefill (docs/serving.md "Cache hierarchy")."""
    import tempfile

    from triton_distributed_tpu.observability import (
        feedback, get_registry)
    from triton_distributed_tpu.observability.anomaly import (
        WINDOW, BaselineStore)
    from triton_distributed_tpu.serving import (
        ContinuousBatchingScheduler, Request)
    from triton_distributed_tpu.serving.scheduler import (
        prefill_baseline_key)

    rng = np.random.default_rng(99)
    sysp = [int(x) for x in rng.integers(1, 61, 32)]  # 2 full pages
    trace = [dict(prompt=sysp + [1 + i, 2 + i],
                  max_new_tokens=4 + (i % 3), seed=500 + i,
                  arrival_time=0.0 if i == 0 else 0.004)
             for i in range(12)]
    sc = SchedulerConfig(num_slots=SLOTS,
                         prefill_buckets=(8, 16, 32, 64),
                         kv_layout="paged", page_size=16)
    # Seeded prefill baseline (what "recompute" is predicted to
    # cost) + a synthetic bus: the ship-vs-recompute model engages
    # deterministically, machine-independently.
    store = BaselineStore(os.path.join(
        tempfile.mkdtemp(prefix="tdt-kvtier-"), "baselines.json"))
    for b in (16, 32, 64):
        for _ in range(WINDOW):
            store.observe(prefill_baseline_key(b), 5000.0)
    # Frozen clock so the scripted snapshot never goes stale on
    # a slow host mid-bench (machine-independence).
    bus = feedback.synthetic_bus(store=store, ts=0.0,
                                 clock=lambda: 0.0)

    def run_fleet(n_replicas, ship):
        from triton_distributed_tpu.observability.lineage import (
            get_lineage_recorder)
        get_lineage_recorder().clear()
        get_registry().clear()
        feedback.clear_recent_decisions()
        cluster = ServingCluster(model, params, ClusterConfig(
            n_replicas=n_replicas,
            scheduler=sc,
            router=RouterConfig(affinity_tokens=0, prefix_ship=ship),
            step_time_s=STEP_S, prefill_time_s=PREFILL_S, bus=bus))
        recs = [cluster.submit(**t) for t in trace]
        done = cluster.drain()
        assert len(done) == len(trace), [r.state for r in recs]
        snap = get_registry().snapshot()
        flips = sum(1 for d in feedback.recent_decisions()
                    if d.consumer == "cluster.kv_fetch"
                    and d.choice == "peer_ship")
        return {
            "streams": [r.tokens for r in
                        sorted(done, key=lambda r: r.record_id)],
            "replicas_used": len({r.replica_history[0]
                                  for r in recs}),
            "prefill_tokens": int(snap["counters"].get(
                "serving_prefix_cache_miss_tokens_total", 0)),
            "ships": int(snap["counters"].get(
                "cluster_prefix_ships_total", 0)),
            "shipped_pages": int(snap["counters"].get(
                "cluster_prefix_pages_shipped_total", 0)),
            "peer_hits": int(snap["counters"].get(
                'serving_kvtier_hit_total{tier="peer"}', 0)),
            "flips": flips,
        }

    # Single-engine reference (exactness) + the once-across-the-fleet
    # prefill-work floor: the whole prompt once, then one private
    # suffix (2 tokens) per later request.
    class _C:
        t = 0.0
    c = _C()
    ref_sched = ContinuousBatchingScheduler(
        model, params, sc, clock=lambda: c.t,
        clock_advance=lambda dt: setattr(c, "t", c.t + dt))
    ref_done = ref_sched.run([Request(**t) for t in trace])
    ref = [r.generated for r in sorted(ref_done,
                                       key=lambda r: r.request_id)]
    floor = len(trace[0]["prompt"]) + 2 * (len(trace) - 1)

    base = run_fleet(1, ship=True)
    no_ship = run_fleet(2, ship=False)
    rows = []
    for n in (1, 2, 4):
        r = base if n == 1 else run_fleet(n, ship=True)
        exact = r["streams"] == ref
        rec = dict(
            bench="router", workload="kvtier_fleet", n_replicas=n,
            mode="prefix_ship",
            fleet_prefill_tokens=r["prefill_tokens"],
            prefix_ships=r["ships"],
            shipped_pages=r["shipped_pages"],
            peer_hits=r["peer_hits"],
            kv_fetch_flips=r["flips"],
            replicas_used=r["replicas_used"],
            prefix_ship_exact=exact,
            zero_second_prefill=(r["prefill_tokens"] == floor),
            fleet_prefill_sublinear=(
                r["prefill_tokens"] < n * base["prefill_tokens"]
                if n > 1 else True),
            peer_ship_flipped=(r["flips"] >= 1 if n > 1 else True),
        )
        if n == 2:
            rec["prefill_tokens_no_ship"] = no_ship["prefill_tokens"]
            rec["ship_beats_recompute"] = (
                r["prefill_tokens"] < no_ship["prefill_tokens"])
        rows.append(rec)
    return rows


def socket_parity_row(model, params, trace):
    """The same cluster twice — once on `VirtualTransport` (virtual
    clock), once as a THREADED socket fleet (real localhost TCP, wall
    clock, `serving/cluster/net`) — under round-robin placement,
    which is a pure function of dispatch order (the PR-8 degradation
    contract): token streams AND routed assignments must match
    exactly, so the wire demonstrably adds transport, not behavior.
    """
    import threading
    import time as _time

    from triton_distributed_tpu.serving.cluster.net import (
        node as _node)
    from triton_distributed_tpu.serving.cluster.net.fabric import (
        NetFabric, _buckets, cluster_clock)
    from triton_distributed_tpu.serving.cluster.net.node import (
        serve_connection)
    from triton_distributed_tpu.serving.cluster.net.remote import (
        PrefillHost, ReplicaHost)
    from triton_distributed_tpu.serving.cluster.net.rendezvous \
        import Directory
    from triton_distributed_tpu.serving.cluster.prefill import (
        PrefillWorker)
    from triton_distributed_tpu.serving.cluster.replica import (
        Replica)

    sc = SchedulerConfig(num_slots=SLOTS, prefill_buckets=BUCKETS)
    cfg = ClusterConfig(
        n_replicas=2, n_prefill_workers=1, scheduler=sc,
        router=RouterConfig(mode="round_robin"),
        step_time_s=STEP_S, prefill_time_s=PREFILL_S)

    def run(fabric, clock):
        cluster = ServingCluster(model, params, cfg, clock=clock,
                                 fabric=fabric)
        recs = [cluster.submit(t["prompt"], t["max_new_tokens"],
                               seed=t["seed"]) for t in trace]
        done = cluster.drain()
        assert len(done) == len(trace), [r.state for r in recs]
        return {
            "assignments": [tuple(r.replica_history) for r in recs],
            "streams": [r.tokens for r in
                        sorted(done, key=lambda r: r.record_id)],
            "kv_shipped_bytes": cluster.transport.shipped_bytes,
            "shipments": cluster.transport.shipments,
        }

    virtual = run(None, None)

    t0 = _time.time()
    clock = cluster_clock(t0)
    ranks = {0: {"role": "router", "index": 0, "addr": "-"}}
    threads = []

    def host_replica(rank, idx, srv):
        rep = Replica(idx, model, params, sc, clock,
                      step_time_s=cfg.step_time_s)
        sock, _ = srv.accept()
        srv.close()
        serve_connection(sock, rank, ReplicaHost(rep).dispatch)

    def host_prefill(rank, idx, srv):
        w = PrefillWorker(idx, model, params, _buckets(model, sc),
                          pad_id=sc.pad_id,
                          prefill_time_s=cfg.prefill_time_s)
        sock, _ = srv.accept()
        srv.close()
        serve_connection(sock, rank, PrefillHost(w).dispatch)

    roles = [("replica", 0, host_replica),
             ("replica", 1, host_replica),
             ("prefill", 0, host_prefill)]
    for rank, (role, idx, fn) in enumerate(roles, start=1):
        srv = _node.listen()
        ranks[rank] = {"role": role, "index": idx,
                       "addr": _node.addr_of(srv)}
        th = threading.Thread(target=fn, args=(rank, idx, srv),
                              daemon=True)
        th.start()
        threads.append(th)
    fabric = NetFabric(Directory(world=4, ranks=ranks, t0=t0),
                       rank=0)
    wall0 = _time.monotonic()
    sock_run = run(fabric, clock)
    wall_ms = (_time.monotonic() - wall0) * 1e3
    fabric.shutdown()
    for th in threads:
        th.join(timeout=10)

    return dict(
        bench="router", workload="socket_parity", n_replicas=2,
        n_prefill=1, mode="paired",
        requests=len(trace),
        kv_shipped_bytes=sock_run["kv_shipped_bytes"],
        shipments=sock_run["shipments"],
        # Wall time is informational ONLY (machine-dependent): the
        # gated facts are the two exactness booleans.
        socket_wall_ms=round(wall_ms, 3),
        socket_matches_virtual=(sock_run["streams"]
                                == virtual["streams"]),
        assignments_exact=(sock_run["assignments"]
                           == virtual["assignments"]),
    )


def hierarchical_rows(trace):
    """Pod-scale routing work accounting (`net/hierarchy.py`): route
    the committed trace through a pod front door (cells of 4) and
    through a flat `ClusterRouter` over the same fleet, counting
    score evaluations — the per-request placement WORK — and per-cell
    prefix-directory growth.  Pure routing (signal-bearing stub
    replicas, no decode): every number is a deterministic function of
    the trace."""
    from triton_distributed_tpu.serving.cluster import ClusterRouter
    from triton_distributed_tpu.serving.cluster.net.hierarchy import (
        make_pod)

    class _Rep:
        def __init__(self, rid):
            self.id = rid
            self.rank = rid
            self.name = f"replica-{rid}"
            self.dead = False
            self.quarantined = False
            self.hb_ts = 0.0
            self.last_step_s = STEP_S
            self.routed_total = 0

        routable = True

        def signals(self, now):
            return {"ts": now, "queue_depth": 0.0,
                    "active_slots": 0.0, "kv_occupancy": 0.0,
                    "step_us": STEP_S * 1e6, "link_busy": 0.0}

    rows = []
    cell_size = 4
    for n_replicas in (16, 32):
        n_cells = n_replicas // cell_size
        pod = make_pod([_Rep(i) for i in range(n_replicas)], n_cells,
                       page_size=4)
        pod.refresh(0.0)
        registered = 0
        for t in trace:
            cell, rep = pod.route(t["prompt"], "decode", now=0.0)
            assert rep is not None
            pod.commit_route(0.0)
            before = len(cell.directory)
            cell.directory.register(t["prompt"], rep.id, now=0.0)
            registered += len(cell.directory) - before
        flat = ClusterRouter(RouterConfig(),
                             [_Rep(i) for i in range(n_replicas)])
        for t in trace:
            assert flat.route(t["prompt"], "decode", now=0.0) \
                is not None
            flat.commit_route(0.0)
        n = len(trace)
        pod_per_req = pod.evals() / n
        flat_per_req = flat.score_evals / n
        cell_per_req = sum(c.router.score_evals
                           for c in pod.cells) / n
        max_dir = max(len(c.directory) for c in pod.cells)
        rows.append(dict(
            bench="router", workload="hierarchical", mode="paired",
            n_replicas=n_replicas, n_cells=n_cells,
            cell_size=cell_size, requests=n,
            pod_evals_per_request=round(pod_per_req, 3),
            flat_evals_per_request=round(flat_per_req, 3),
            cell_evals_per_request=round(cell_per_req, 3),
            directory_chains_total=registered,
            directory_chains_max_cell=max_dir,
            # Per-request CELL work is the cell size — independent of
            # pod scale (the O(cell) claim).
            work_o_cell=(cell_per_req == float(cell_size)),
            # No single cell's directory holds the pod's chains.
            directory_o_cell=(n_cells == 1
                              or max_dir * 2 <= max(registered, 1)),
            # Total pod routing work stays under the flat router's
            # O(pod) — sub-linear overhead as the fleet grows.
            sublinear_vs_flat=(pod_per_req < flat_per_req),
        ))
    # The pod-scale pitch in one pair of numbers: doubling the fleet
    # doubles flat work but only adds front-door cells to pod work.
    assert rows[1]["flat_evals_per_request"] == 2 * \
        rows[0]["flat_evals_per_request"]
    assert rows[1]["pod_evals_per_request"] < \
        rows[1]["flat_evals_per_request"]
    return rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None,
                    help="also write the JSON lines here (committed "
                         "copy: benchmark/results/router.json)")
    args = ap.parse_args()
    out = open(args.out, "w") if args.out else None
    rows = []

    def emit(rec):
        rows.append(rec)
        line = json.dumps(rec)
        print(line)
        if out is not None:
            out.write(line + "\n")

    model = ToyModel(ToyConfig(vocab_size=61, hidden=16,
                               max_seq_len=64))
    params = model.init_params(jax.random.key(0))
    trace = build_trace()

    def strip(r):
        return {k: v for k, v in r.items()
                if k not in ("assignments", "streams")}

    # -- scale: N replicas vs the single engine -------------------------
    single = run_cluster(model, params, trace, 1, "signal_aware")
    for n in (1, 2, 4):
        r = (single if n == 1
             else run_cluster(model, params, trace, n,
                              "signal_aware"))
        assert r["streams"] == single["streams"], (
            "replica count changed a token stream")
        emit(dict(bench="router", workload="scale", n_replicas=n,
                  mode="signal_aware", **strip(r),
                  speedup_vs_single=round(single["ms"] / r["ms"], 4)))

    # -- disaggregated: dedicated prefill + KV shipping -----------------
    r = run_cluster(model, params, trace, 2, "signal_aware",
                    workers=1)
    assert r["streams"] == single["streams"], (
        "prefill shipping changed a token stream")
    assert r["shipments"] == N_REQUESTS
    emit(dict(bench="router", workload="disagg", n_replicas=2,
              n_prefill=1, mode="signal_aware", **strip(r)))

    # -- imbalance: signal-aware must beat round-robin ------------------
    for name, kw in (
        ("imbalance_straggler", dict(straggle=(0, 3.0))),
        ("imbalance_hot_link", dict(link_busy=(0, 0.65))),
    ):
        rr = run_cluster(model, params, trace, 3, "round_robin", **kw)
        sa = run_cluster(model, params, trace, 3, "signal_aware",
                         **kw)
        assert sa["streams"] == rr["streams"] == single["streams"], (
            "placement changed a token stream")
        for mode, r in (("round_robin", rr), ("signal_aware", sa)):
            emit(dict(bench="router", workload=name, n_replicas=3,
                      mode=mode, **strip(r)))
        emit(dict(bench="router", workload=name, n_replicas=3,
                  mode="paired",
                  speedup_makespan=round(rr["ms"] / sa["ms"], 4),
                  speedup_ttft=round(rr["mean_ttft_ms"]
                                     / sa["mean_ttft_ms"], 4),
                  signal_aware_beats_rr=sa["ms"] < rr["ms"]))

    # -- KV tier: shared-prefix fleet (peer prefix shipping) ------------
    for rec in kvtier_fleet_rows(model, params):
        emit(rec)

    # -- real wire: socket fleet vs virtual, assignment-exact -----------
    sp = socket_parity_row(model, params, trace[:10])
    assert sp["socket_matches_virtual"], (
        "socket transport changed a token stream")
    assert sp["assignments_exact"], (
        "socket transport changed a routed assignment")
    emit(sp)

    # -- pod scale: hierarchical routing work vs flat -------------------
    for rec in hierarchical_rows(trace):
        assert rec["work_o_cell"] and rec["sublinear_vs_flat"], rec
        emit(rec)

    # -- balanced: signal-aware must match round-robin exactly ----------
    htrace = build_trace(homogeneous=True)
    rr = run_cluster(model, params, htrace, 3, "round_robin")
    sa = run_cluster(model, params, htrace, 3, "signal_aware")
    emit(dict(bench="router", workload="balanced", n_replicas=3,
              mode="paired",
              speedup_makespan=round(rr["ms"] / sa["ms"], 4),
              matches_round_robin=(sa["assignments"]
                                   == rr["assignments"]
                                   and sa["streams"] == rr["streams"]),
              signal_aware_never_worse=sa["ms"] <= rr["ms"] + 1e-9))

    if out is not None:
        out.close()
    paired = [r for r in rows if r.get("mode") == "paired"]
    assert all(r.get("signal_aware_beats_rr", True) for r in paired)
    assert all(r.get("matches_round_robin", True) for r in paired), (
        "balanced signal-aware placement diverged from round-robin")
    return 0


if __name__ == "__main__":
    sys.exit(main())
