"""Capacity-planner bench: the virtual-clock SLO sweep as bench rows.

A thin emitter over `observability.planner.plan` — the same seeded
trace, the same replay on router + replicas + scheduler, the same
`slo.evaluate_outcomes` scoring.  Emitted rows (one JSON line each,
``bench: "planner"``):

- ``workload: "cell"`` — one per (rate multiplier, replica count)
  tried: per-class compliance/objective/p99s, cell ok flag, virtual
  makespan;
- ``workload: "plan"`` — one per rate: ``min_replicas`` (the
  smallest fleet holding every class's objective), ``plan_feasible``
  and ``plan_deterministic`` (the winning cell re-run and
  byte-compared — a capacity answer that varies run-to-run is a
  bug, not noise).

Gate semantics (`scripts/check_bench_regression.py
planner_checks`): every fresh plan row must be feasible AND
deterministic, and every cell's compliance must sit in [0, 1].
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # repo root

import argparse
import json

import jax

from triton_distributed_tpu.observability import planner as planner_mod
from triton_distributed_tpu.serving import ToyConfig, ToyModel


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None,
                    help="also write the JSON lines here (committed "
                         "copy: benchmark/results/planner.json)")
    ap.add_argument("--replicas-max", type=int, default=4)
    ap.add_argument("--rates", default="1.0,2.0")
    ap.add_argument("--requests", type=int, default=24)
    ap.add_argument("--seed", type=int, default=1234)
    args = ap.parse_args()
    out = open(args.out, "w") if args.out else None

    def emit(rec):
        line = json.dumps(rec)
        print(line)
        if out is not None:
            out.write(line + "\n")

    model = ToyModel(ToyConfig(vocab_size=61, hidden=16,
                               max_seq_len=64))
    params = model.init_params(jax.random.key(0))
    rates = [float(r) for r in args.rates.split(",") if r]
    result = planner_mod.plan(
        model, params, replicas_max=args.replicas_max, rates=rates,
        n_requests=args.requests, seed=args.seed)
    for rate_row in result["rates"]:
        rate = rate_row["rate_multiplier"]
        for cell in rate_row["cells"]:
            per_class = {
                name: {"compliance": v["compliance"],
                       "objective": v["objective"],
                       "ok": v["ok"],
                       "p99_ttft_ms": v["p99_ttft_ms"],
                       "p99_tbt_ms": v["p99_tbt_ms"]}
                for name, v in sorted(cell["classes"].items())}
            emit(dict(bench="planner", workload="cell",
                      rate_multiplier=rate,
                      n_replicas=cell["n_replicas"],
                      cell_ok=cell["ok"], ms=cell["ms"],
                      finished=cell["finished"],
                      per_class=per_class))
        emit(dict(bench="planner", workload="plan",
                  rate_multiplier=rate,
                  replicas_max=result["replicas_max"],
                  n_requests=result["n_requests"],
                  seed=result["seed"],
                  min_replicas=rate_row["min_replicas"],
                  plan_feasible=rate_row["feasible"],
                  plan_deterministic=rate_row["deterministic"]))
    if out is not None:
        out.close()


if __name__ == "__main__":
    main()
