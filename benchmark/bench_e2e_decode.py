"""End-to-end decode throughput: Qwen3-0.6B-shaped model, full
serving stack (Engine scan rollout: fused-Pallas layers, donated KV
cache, fused sampling) on the available chip(s).

Timing: the scan rollout is ONE dispatch for all gen_len steps, so the
per-token latency is the slope between two gen_len values — prefill,
cache allocation, dispatch and fetch costs cancel exactly.

Emits one JSON line per mode (fused vs plain-XLA layers).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # repo root

import argparse
import json
import statistics
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from triton_distributed_tpu.models import ModelConfig
from triton_distributed_tpu.models.engine import Engine
from triton_distributed_tpu.models.qwen import Qwen3


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--prefill", type=int, default=128)
    # The slope denominator (g2 - g1) sets the noise floor: each
    # sample pays two tunnel fetches whose jitter is fixed, so the
    # per-step slope error scales as jitter / (g2 - g1).  The round-3
    # ratio_range of [0.475, 1.769] came from a 128-step denominator;
    # 480 steps cuts the same jitter to ~±8% (VERDICT r3 next #6).
    ap.add_argument("--g1", type=int, default=32)
    ap.add_argument("--g2", type=int, default=512)
    ap.add_argument("--repeats", type=int, default=6)
    ap.add_argument("--layers", type=int, default=0,
                    help="override layer count (0 = config default)")
    args = ap.parse_args()

    devices = jax.devices()
    mesh = Mesh(np.array(devices), ("tp",))
    cfg = ModelConfig.qwen3_0_6b()
    if args.layers:
        cfg.num_layers = args.layers
    cfg.max_seq_len = args.prefill + args.g2 + 8

    b = args.batch
    ids = jax.random.randint(jax.random.key(0), (b, args.prefill), 0,
                             cfg.vocab_size)

    # Build BOTH modes up front and interleave their measurements in
    # ABBA order: the tunneled chip shows minutes-scale drift, and a
    # sequential per-mode sweep folds that drift into the ratio (round
    # 2 reported fused 0.96x from exactly this artifact; interleaved,
    # the two modes tie at world=1 — their decode graphs are
    # equivalent there).
    runners = {}
    for mode in ("fused", "xla"):
        model = Qwen3(cfg, mesh, mode=mode)
        params = model.init_params(jax.random.key(1))
        eng = Engine(model)

        def run(gen_len, model=model, params=params, eng=eng):
            cache = model.create_cache(b)
            logits, cache = eng.prefill(params, ids, cache)
            first = jnp.argmax(logits, -1).astype(jnp.int32)
            t0 = time.perf_counter()
            toks, _ = eng._rollout(params, first, cache,
                                   jax.random.key(2), gen_len)
            np.asarray(toks[0, 0])          # fence: full queue drain
            return time.perf_counter() - t0

        run(args.g1)  # warm both jits (prefill warmed inside)
        run(args.g2)
        runners[mode] = run

    slopes = {m: [] for m in runners}
    rounds = []
    for _ in range(args.repeats):
        rnd = {}
        for m in ("fused", "xla", "xla", "fused"):   # ABBA
            t1 = runners[m](args.g1)
            t2 = runners[m](args.g2)
            # A tunnel-fetch glitch can make t2 < t1; a non-positive
            # slope is always measurement garbage — DISCARD the
            # sample (clamping would leak an absurd sentinel into the
            # paired ratios and the median).
            sl = (t2 - t1) / (args.g2 - args.g1)
            if sl > 0:
                slopes[m].append(sl)
                rnd.setdefault(m, []).append(sl)
        rounds.append(rnd)

    results = {m: statistics.median(sl) for m, sl in slopes.items()}
    # Paired per-round ratios expose the noise band the medians hide:
    # at world=1 the two modes' decode graphs are equivalent (the only
    # HLO diff is two world-1 no-op all_gathers), so any deviation of
    # the ratio from 1.0 here bounds the harness noise, not a real
    # fused overhead.  Each round's ratio SUMS its two adjacent
    # samples per mode (ABBA); and because the four slopes of a round
    # measure equivalent programs seconds apart, a round whose own
    # max/min slope spread exceeds 1.5x contains a tunnel glitch (a
    # late fetch collapsing one slope) and is DISCARDED — the count is
    # reported so a glitchy run is visibly a glitchy run.
    kept, discarded = [], 0
    for r in rounds:
        four = r.get("xla", []) + r.get("fused", [])
        if len(four) != 4:
            discarded += 1
            continue
        if max(four) / min(four) > 1.5:
            discarded += 1
            continue
        kept.append(sum(r["xla"]) / sum(r["fused"]))
    pair_ratios = sorted(kept) or [float("nan")]
    world = len(devices)
    # Fixed-regime tag (VERDICT r4 weak #4): rounds are only
    # comparable when (B, layers, gen_span) match; the default
    # invocation IS the pinned regime, so every round's committed
    # artifact carries a like-for-like decode row.
    pinned = (b == 8 and not args.layers
              and (args.g1, args.g2) == (32, 512))
    regime = (f"pinned-B8-L{cfg.num_layers}-g32-512" if pinned
              else "custom")
    for mode in ("fused", "xla"):
        per_step = results[mode]
        print(json.dumps({
            "bench": "e2e_decode", "mode": mode, "B": b,
            "layers": cfg.num_layers,
            "regime": regime,
            "gen_span": [args.g1, args.g2],
            "ms_per_step": round(per_step * 1e3, 3),
            "tokens_per_s": round(b / per_step, 1),
            **({"vs_baseline":
                round(statistics.median(pair_ratios), 3),
                "ratio_range": [round(pair_ratios[0], 3),
                                round(pair_ratios[-1], 3)],
                "rounds_kept": len(kept),
                "rounds_discarded_glitch": discarded,
                # At world=1 the two modes' decode graphs are
                # HLO-equivalent: the ratio bounds harness noise and
                # is NOT overlap-speedup evidence (that exists only at
                # world > 1).
                "degenerate_world1_tie": world <= 1}
               if mode == "xla" else {}),
        }), flush=True)


if __name__ == "__main__":
    main()
