"""End-to-end decode throughput: Qwen3-0.6B-shaped model, full
serving stack (Engine scan rollout: fused-Pallas layers, donated KV
cache, fused sampling) on the available chip(s).

Timing: the scan rollout is ONE dispatch for all gen_len steps, so the
per-token latency is the slope between two gen_len values — prefill,
cache allocation, dispatch and fetch costs cancel exactly.

Emits one JSON line per mode (fused vs plain-XLA layers).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # repo root

import argparse
import json
import statistics
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from triton_distributed_tpu.models import ModelConfig
from triton_distributed_tpu.models.engine import Engine
from triton_distributed_tpu.models.qwen import Qwen3


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--prefill", type=int, default=128)
    ap.add_argument("--g1", type=int, default=32)
    ap.add_argument("--g2", type=int, default=160)
    ap.add_argument("--repeats", type=int, default=4)
    ap.add_argument("--layers", type=int, default=0,
                    help="override layer count (0 = config default)")
    args = ap.parse_args()

    devices = jax.devices()
    mesh = Mesh(np.array(devices), ("tp",))
    cfg = ModelConfig.qwen3_0_6b()
    if args.layers:
        cfg.num_layers = args.layers
    cfg.max_seq_len = args.prefill + args.g2 + 8

    b = args.batch
    ids = jax.random.randint(jax.random.key(0), (b, args.prefill), 0,
                             cfg.vocab_size)

    results = {}
    for mode in ("fused", "xla"):
        model = Qwen3(cfg, mesh, mode=mode)
        params = model.init_params(jax.random.key(1))
        eng = Engine(model)

        def run(gen_len):
            cache = model.create_cache(b)
            logits, cache = eng.prefill(params, ids, cache)
            first = jnp.argmax(logits, -1).astype(jnp.int32)
            t0 = time.perf_counter()
            toks, _ = eng._rollout(params, first, cache,
                                   jax.random.key(2), gen_len)
            np.asarray(toks[0, 0])          # fence: full queue drain
            return time.perf_counter() - t0

        run(args.g1)  # warm both jits (prefill warmed inside)
        run(args.g2)
        slopes = []
        for _ in range(args.repeats):
            t1 = run(args.g1)
            t2 = run(args.g2)
            slopes.append((t2 - t1) / (args.g2 - args.g1))
        per_step = statistics.median(slopes)
        results[mode] = per_step
        print(json.dumps({
            "bench": "e2e_decode", "mode": mode, "B": b,
            "layers": cfg.num_layers,
            "ms_per_step": round(per_step * 1e3, 3),
            "tokens_per_s": round(b / per_step, 1),
            **({"vs_baseline":
                round(results["xla"] / results["fused"], 3)}
               if "xla" in results and "fused" in results else {}),
        }), flush=True)


if __name__ == "__main__":
    main()
