"""Paired static-vs-closed-loop method selection under seeded link
contention — the ISSUE-8 acceptance benchmark.

A *modeled* benchmark, deliberately: the scenario a closed loop pays
off in — a decode allreduce contending one torus axis while an
ag-gemm-style collective picks its schedule — cannot be produced on a
CPU CI host, and even on hardware it is not reproducible enough to
gate on.  So the scenario is SEEDED: a synthetic feedback bus scripts
the background utilization (`observability.feedback.synthetic_bus`),
the static and closed-loop choosers each pick a method, and both
picks are costed under the scenario's ground-truth contended cost
model (residual-bandwidth derated analytic estimates — the same
ICI tables every `estimate_*` in `kernels/comm_perf_model.py` uses,
pinned `closed_ring=True` so the numbers are machine-independent).

Emitted rows (one JSON line each, ``bench: "closed_loop"``):

- per (chooser, scenario, size): ``mode: "static" | "closed_loop"``
  with the chosen method and its ground-truth ``modeled_us``;
- one paired summary per chooser: flip count, mean/min speedup of
  closed-loop over static across the sweep.

Gate semantics (`scripts/check_bench_regression.py`): the ``static``
rows are what a bus-disabled run produces — they are pure analytic
model output and must match the committed results EXACTLY (any drift
means the static selection behavior changed, the one thing the
closed loop must never do).  The gate enforces equality for them, on
top of the usual latency tolerance for everything else.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # repo root

import argparse
import json

from triton_distributed_tpu.kernels.comm_perf_model import (
    IciSpec,
    estimate_all_gather_time_us,
    estimate_one_shot_time_us,
    estimate_torus_ag_time_us,
    one_shot_beats_ring,
    torus_beats_single_axis,
)
from triton_distributed_tpu.observability.feedback import (
    effective_spec,
    synthetic_bus,
)

#: Fixed chip model so committed numbers are machine-independent
#: (the v5e row of the published table; `get_ici_spec` would read
#: whatever device the host fakes).
SPEC = IciSpec(link_gbps=50.0, num_links=4, latency_us=1.0)

#: Seeded background-load scenarios: a decode allreduce saturating
#: one axis (the ROADMAP-4 motivating case) and a milder mixed load.
SCENARIOS = {
    "decode_ar_on_x": {"x:0>1": 0.85, "x:1>2": 0.85, "x:2>3": 0.85},
    "mixed_60": {"x:0>1": 0.6, "y:0>1": 0.2},
}

SIZES = [1 << e for e in range(10, 24, 2)]


def _truth_torus(nbytes, sizes, sig):
    """Ground-truth contended cost of each torus-chooser candidate."""
    axes = ("x", "y")
    world = 1
    for s in sizes:
        world *= s
    t_torus = estimate_torus_ag_time_us(
        nbytes, sizes,
        effective_spec(SPEC, sig.mean_busy_fraction(axes)),
        closed_ring=True)
    spec1 = effective_spec(SPEC, sig.busy_fraction("x"))
    t_single = min(
        estimate_all_gather_time_us(nbytes, world, spec1,
                                    closed_ring=True),
        estimate_one_shot_time_us(nbytes, world, spec1,
                                  closed_ring=True))
    return {"torus": t_torus, "single_axis": t_single}


def _truth_ring(nbytes, world, sig):
    spec = effective_spec(SPEC, sig.busy_fraction("x"))
    return {
        "one_shot": estimate_one_shot_time_us(nbytes, world, spec,
                                              closed_ring=True),
        "ring": estimate_all_gather_time_us(nbytes, world, spec,
                                            closed_ring=True),
    }


def sweep(out):
    rows = []

    def emit(rec):
        rows.append(rec)
        line = json.dumps(rec)
        print(line)
        if out is not None:
            out.write(line + "\n")

    for chooser, pick, truth in (
        ("torus_vs_single",
         lambda nb, bus: ("torus" if torus_beats_single_axis(
             nb, (4, 4), SPEC, axes=("x", "y"), bus=bus)
             else "single_axis"),
         lambda nb, sig: _truth_torus(nb, (4, 4), sig)),
        ("one_shot_vs_ring",
         lambda nb, bus: ("one_shot" if one_shot_beats_ring(
             nb, 16, SPEC, axis="x", bus=bus)
             else "ring"),
         lambda nb, sig: _truth_ring(nb, 16, sig)),
    ):
        for scenario, util in SCENARIOS.items():
            bus = synthetic_bus(link_utilization=util)
            sig = bus.read()
            # Static picks go through an explicitly EMPTY bus: the
            # degradation contract makes that bit-identical to no bus
            # at all, and it keeps the rows immune to an ambient
            # TDT_CLOSED_LOOP=1 in the environment.
            empty = synthetic_bus()
            speedups = []
            flips = 0
            for nb in SIZES:
                static_m = pick(nb, empty)
                closed_m = pick(nb, bus)
                costs = truth(nb, sig)
                for mode, method in (("static", static_m),
                                     ("closed_loop", closed_m)):
                    emit({"bench": "closed_loop",
                          "chooser": chooser,
                          "scenario": scenario, "nbytes": nb,
                          "mode": mode, "chosen": method,
                          "modeled_us": round(costs[method], 3)})
                speedups.append(costs[static_m] / costs[closed_m])
                flips += static_m != closed_m
            emit({"bench": "closed_loop", "chooser": chooser,
                  "scenario": scenario, "mode": "paired",
                  "flips": flips, "n_sizes": len(SIZES),
                  "mean_speedup": round(sum(speedups)
                                        / len(speedups), 4),
                  "min_speedup": round(min(speedups), 4),
                  "max_speedup": round(max(speedups), 4),
                  "closed_loop_never_worse":
                      min(speedups) >= 1.0 - 1e-9})
    return rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None,
                    help="also append the JSON lines here (the "
                         "committed copy lives at "
                         "benchmark/results/closed_loop.json)")
    args = ap.parse_args()
    out = open(args.out, "w") if args.out else None
    rows = sweep(out)
    if out is not None:
        out.close()
    paired = [r for r in rows if r.get("mode") == "paired"]
    assert all(r["closed_loop_never_worse"] for r in paired), paired
    total_flips = sum(r["flips"] for r in paired)
    assert total_flips > 0, "seeded contention never flipped a choice"
    return 0


if __name__ == "__main__":
    sys.exit(main())
