"""03 — Two-level AllGather (ICI-slice × DCN).

Reference: `tutorials/03-inter-node-allgather.py` (2D ring: NVLink
inside the node, IB between nodes).

On TPU the fast domain is the ICI slice and the slow one is DCN, which
only supports XLA collectives — so the two-level schedule is: each
shard crosses DCN exactly once (m rows per device, the scarce-resource
minimum), then the Pallas ring fans the aggregated slice data out over
ICI. Here the 8 CPU devices play a (2 slices × 4 chips) topology.
"""

import functools
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])
from examples._bootstrap import make_mesh  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from triton_distributed_tpu.kernels.hierarchical import (  # noqa: E402
    HierarchicalContext,
    all_gather_2d,
)
from triton_distributed_tpu.ops import shard_map_op  # noqa: E402


def main():
    mesh = make_mesh(("dcn", "ici"), (2, 4))
    hctx = HierarchicalContext(ici_axis="ici", dcn_axis="dcn",
                               ici_size=4, dcn_size=2)
    x = jax.random.normal(jax.random.key(0), (8 * 8, 128))

    fn = shard_map_op(functools.partial(all_gather_2d, ctx=hctx), mesh,
                      in_specs=P(("dcn", "ici"), None),
                      out_specs=P(None, None))
    out = jax.jit(fn)(x)
    assert jnp.array_equal(out, x)
    print("03_hierarchical_allgather OK on a (2 x 4) dcn x ici mesh")


if __name__ == "__main__":
    main()
