"""09 — Int8 (W8A8) quantized fused AllGather-GEMM.

Beyond reference parity: the reference's AG-GEMM family is
half-precision only (fp8 appears there just as an AllToAll payload
format).  On TPU, quantizing the overlap op wins twice —

  1. the ring forwards int8 chunks: HALF the ICI bytes of bf16, and
  2. each held chunk feeds the MXU's int8 path: 2x the bf16 peak
     (v5e: 394 TOPS vs 197 TFLOP/s; measured 326 TOPS at 4096^3),

so the comm/compute balance point of the overlap shifts in our favor
on both sides.  Per-row activation scales travel in one tiny XLA
all_gather; per-output-channel weight scales are resident; the int32
accumulator is dequantized by a rank-1 epilogue.
"""

import functools
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])
from examples._bootstrap import make_mesh  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from triton_distributed_tpu.kernels.allgather_gemm import (  # noqa: E402
    AllGatherGEMMContext,
    ag_gemm_w8a8,
)
from triton_distributed_tpu.kernels.quantized import (  # noqa: E402
    Int8MatmulConfig,
    quantize_sym,
)
from triton_distributed_tpu.ops import shard_map_op  # noqa: E402


def main():
    mesh = make_mesh()
    world = mesh.shape["tp"]
    m_loc, k, n = 16, 256, 128 * world
    a = jax.random.normal(jax.random.key(0), (world * m_loc, k)) / 4
    w = jax.random.normal(jax.random.key(1), (k, n)) / 4

    # Quantize the weights ONCE (per output channel), offline.
    w_q, w_scale = quantize_sym(w, axis=0)

    ctx = AllGatherGEMMContext(axis="tp", world_size=world,
                               method="fused")
    fn = shard_map_op(
        functools.partial(ag_gemm_w8a8, ctx=ctx,
                          config=Int8MatmulConfig(16, 128, 128)),
        mesh,
        in_specs=(P("tp", None), P(None, "tp"), P("tp")),
        out_specs=P(None, "tp"))
    out = jax.jit(fn)(a, w_q, w_scale)

    # Golden: dequantized float reference.
    a_q, a_scale = quantize_sym(a, axis=1)
    ref = (a_q.astype(jnp.float32) * a_scale[:, None]) @ (
        w_q.astype(jnp.float32) * w_scale[None, :])
    err = float(jnp.abs(out.astype(jnp.float32) - ref).max())
    assert err < 0.02 * float(jnp.abs(ref).max()), err
    print(f"09 w8a8 overlap OK: out {out.shape}, max dequant err {err:.2e}")


if __name__ == "__main__":
    main()
