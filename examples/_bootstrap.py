"""Shared example bootstrap: an 8-virtual-device CPU mesh unless real
TPUs are attached (same harness as tests/conftest.py)."""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import Mesh  # noqa: E402

if os.environ.get("JAX_PLATFORMS", "") != "tpu":
    # Examples default to the 8-device CPU simulation (site hooks may
    # have imported jax already, so set the config, not just the env);
    # on a real pod run with JAX_PLATFORMS=tpu.
    jax.config.update("jax_platforms", "cpu")


def make_mesh(axes=("tp",), shape=None):
    devs = jax.devices()
    shape = shape or (len(devs),)
    n = int(np.prod(shape))
    return Mesh(np.array(devs[:n]).reshape(shape), axes)
