"""11 — Multi-axis torus collectives (2-axis quarters, 3-axis sextants).

Reference: the push-2d/push-3d escalation of
`python/triton_dist/kernels/nvidia/low_latency_allgather.py:345-400` —
exploit every level of the interconnect topology at once.

A single-axis ring drives at most 2 of a TPU chip's ICI links.  The
torus schedule splits the shard into 2·nd pieces and runs 2·nd
concurrent ring lanes (one per cyclic axis rotation × direction), so a
v5e 2D torus keeps all 4 links busy and a v4/v5p 3D torus all 6 —
~nd× a bidirectional ring's bandwidth.  `ag_gemm`/`gemm_rs` accept a
`TorusContext` directly and consume pieces in arrival order; the
training duals (`ag_gemm_diff`) ride the same schedule backward.

Here the 8 CPU devices play a (2, 2, 2) 3D torus.
"""

import functools
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])
from examples._bootstrap import make_mesh  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from triton_distributed_tpu.kernels.allgather_gemm import ag_gemm  # noqa: E402
from triton_distributed_tpu.kernels.torus import (  # noqa: E402
    TorusContext,
    all_gather_torus,
    all_reduce_torus,
)
from triton_distributed_tpu.ops import shard_map_op  # noqa: E402

XYZ = ("x", "y", "z")


def main():
    mesh = make_mesh(XYZ, (2, 2, 2))
    # method="torus" forces the 6-sextant schedule (the "auto"
    # perf-model crossover would route these tiny demo payloads to the
    # XLA fallback).
    tctx = TorusContext(axes=XYZ, sizes=(2, 2, 2), method="torus")

    # AllGather over all three axes at once.
    x = jax.random.normal(jax.random.key(0), (8 * 12, 128))
    ag = shard_map_op(functools.partial(all_gather_torus, ctx=tctx),
                      mesh, in_specs=P(XYZ, None),
                      out_specs=P(None, None))
    out = jax.jit(ag)(x)
    assert jnp.array_equal(out, x)

    # AllReduce = torus RS -> torus AG, all links busy in both phases.
    xr = jax.random.normal(jax.random.key(1), (8, 16, 128))
    ar = shard_map_op(lambda a: all_reduce_torus(a[0], tctx), mesh,
                      in_specs=P(XYZ, None, None),
                      out_specs=P(None, None))
    red = jax.jit(ar)(xr)
    assert jnp.allclose(red, xr.sum(0), atol=1e-4)

    # Fused torus AG-GEMM: pieces matmul'ed in arrival order while the
    # rest ride the six links.
    a = jax.random.normal(jax.random.key(2), (8 * 12, 64)) / 8
    b = jax.random.normal(jax.random.key(3), (64, 8 * 32)) / 8
    agg = shard_map_op(lambda aa, bb: ag_gemm(aa, bb, tctx), mesh,
                       in_specs=(P(XYZ, None), P(None, XYZ)),
                       out_specs=P(None, XYZ))
    c = jax.jit(agg)(a, b)
    assert jnp.allclose(c, a @ b, atol=2e-3)

    print("torus collectives (3-axis sextants): OK")


if __name__ == "__main__":
    main()
