"""10 — differentiable ring attention: long-context TRAINING.

Beyond the reference's scope (its SP attention is inference-only):
`sp_ring_attention_diff` runs a causal ring — the KV shard travels the
ICI ring while every rank folds the chunk it holds into a running
online-softmax state — with a Pallas BACKWARD per chunk behind a
custom VJP.  `jax.grad` differentiates the whole ring end-to-end:

- the lse-merge is exact (the lse cotangent folds into the flash
  backward's delta term), and
- neither the S x S score matrix nor the gathered KV ever
  materializes, forward or backward — the memory that makes
  million-token training contexts possible.

This example trains a toy objective: push the sharded ring attention's
output toward a target, and checks the gradient against autodiff
through the dense O(S^2) reference.
"""

import functools
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])
from examples._bootstrap import make_mesh  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from triton_distributed_tpu.kernels.flash_attention import (  # noqa: E402
    attention_reference,
)
from triton_distributed_tpu.kernels.sp_ag_attention import (  # noqa: E402
    sp_ring_attention_diff,
)
from triton_distributed_tpu.ops import shard_map_op  # noqa: E402


def main():
    mesh = make_mesh(("sp",), (4,))
    b, h, s, d = 1, 2, 256, 32
    keys = jax.random.split(jax.random.key(0), 4)
    q = jax.random.normal(keys[0], (b, h, s, d), jnp.float32)
    k = jax.random.normal(keys[1], (b, h, s, d), jnp.float32)
    v = jax.random.normal(keys[2], (b, h, s, d), jnp.float32)
    target = jax.random.normal(keys[3], (b, h, s, d), jnp.float32)

    ring = shard_map_op(
        functools.partial(sp_ring_attention_diff, axis="sp",
                          block_q=32, block_k=32),
        mesh,
        in_specs=(P(None, None, "sp", None),) * 3,
        out_specs=P(None, None, "sp", None))

    def loss_ring(q, k, v):
        return jnp.mean((ring(q, k, v) - target) ** 2)

    def loss_ref(q, k, v):
        out = attention_reference(q, k, v, causal=True)
        return jnp.mean((out - target) ** 2)

    val, grads = jax.jit(jax.value_and_grad(
        loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)

    # One SGD step actually reduces the loss.
    lr = 1e-1
    q2, k2, v2 = (x - lr * g for x, g in zip((q, k, v), grads))
    val2 = jax.jit(loss_ring)(q2, k2, v2)

    errs = [float(jnp.abs(a - b).max() / (jnp.abs(b).max() + 1e-9))
            for a, b in zip(grads, g_ref)]
    print(f"loss {float(val):.4f} -> {float(val2):.4f} after one step; "
          f"grad rel errs dq/dk/dv: "
          + ", ".join(f"{e:.2e}" for e in errs))
    assert float(val2) < float(val)
    assert all(e < 2e-2 for e in errs)
    print("OK")


if __name__ == "__main__":
    main()
