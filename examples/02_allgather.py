"""02 — AllGather: ring vs one-shot push.

Reference: `tutorials/02-intra-node-allgather.py` (copy-engine and
NVSHMEM-put variants with per-rank readiness flags).

Two schedules with opposite trade-offs:
- RING: world-1 single-hop steps; every link carries each shard once —
  bandwidth-optimal for big payloads.
- PUSH_ALL: every rank pushes its shard to all peers at once; one hop
  of latency — wins for small (decode-sized) payloads.
`AllGatherContext.resolve_method` picks by an analytic ICI perf model.
"""

import functools
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])
from examples._bootstrap import make_mesh  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from triton_distributed_tpu.kernels.allgather import (  # noqa: E402
    AllGatherContext,
    AllGatherMethod,
    all_gather,
)
from triton_distributed_tpu.ops import shard_map_op  # noqa: E402


def main():
    mesh = make_mesh()
    world = mesh.shape["tp"]
    x = jax.random.normal(jax.random.key(0), (world * 16, 128))

    for method in (AllGatherMethod.RING, AllGatherMethod.PUSH_ALL):
        ctx = AllGatherContext(axis="tp", world_size=world, method=method)
        fn = shard_map_op(functools.partial(all_gather, ctx=ctx), mesh,
                          in_specs=P("tp", None), out_specs=P(None, None))
        out = jax.jit(fn)(x)
        assert jnp.array_equal(out, x), method
        print(f"02_allgather {method.value:9s} OK "
              f"({world} devices, {x.nbytes // world} B/shard)")

    # The auto-select: tiny payloads go one-shot, big ones ring.
    small = AllGatherContext(axis="tp", world_size=world)
    print("auto @ 1 KiB   ->", small.resolve_method(1024).value)
    print("auto @ 16 MiB  ->", small.resolve_method(16 << 20).value)


if __name__ == "__main__":
    main()
