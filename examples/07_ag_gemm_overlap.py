"""07 — Fused AllGather-GEMM: the flagship overlap op.

Reference: `tutorials/07-overlapping-allgather-gemm.py` and
`allgather_gemm.py`: a producer streams A-shards while a persistent
GEMM consumer waits per-rank readiness flags and eats tiles in
rank-swizzled order (own chunk first).

TPU version (ONE kernel): each step forwards the freshest chunk to the
right neighbor (async remote DMA) and feeds the chunk already held
into the MXU matmul pipeline — the DMA of chunk s+1 hides behind the
matmul of chunk s. Per-chunk recv semaphores are the readiness flags.
Decode-sized M auto-selects the one-shot "ll" path instead
(see `AllGatherGEMMContext.resolve_method`).
"""

import functools
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])
from examples._bootstrap import make_mesh  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from triton_distributed_tpu.kernels.allgather_gemm import (  # noqa: E402
    AllGatherGEMMContext,
    ag_gemm,
)
from triton_distributed_tpu.kernels.matmul import MatmulConfig  # noqa: E402
from triton_distributed_tpu.ops import shard_map_op  # noqa: E402


def main():
    mesh = make_mesh()
    world = mesh.shape["tp"]
    m_loc, k, n_loc = 16, 256, 128
    a = jax.random.normal(jax.random.key(0), (world * m_loc, k)) / 16
    b = jax.random.normal(jax.random.key(1), (k, world * n_loc)) / 16

    for method, m_use in (("fused", m_loc), ("ll", 2)):
        ctx = AllGatherGEMMContext(axis="tp", world_size=world,
                                   method=method,
                                   gemm=MatmulConfig(64, 128, 128))
        fn = shard_map_op(functools.partial(ag_gemm, ctx=ctx), mesh,
                          in_specs=(P("tp", None), P(None, "tp")),
                          out_specs=P(None, "tp"))
        aa = a[:world * m_use]
        out = jax.jit(fn)(aa, b)
        ref = aa @ b
        assert float(jnp.abs(out - ref).max()) < 2e-3, method
        print(f"07_ag_gemm {method:5s} OK  M={world * m_use} "
              f"(ring-overlap)" if method == "fused" else
              f"07_ag_gemm {method:5s} OK  M={world * m_use} "
              f"(one-shot + single B pass)")


if __name__ == "__main__":
    main()
