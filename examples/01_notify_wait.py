"""01 — notify / wait: the primitive everything else is built from.

Reference: `tutorials/01-distributed-notify-wait.py`, where a producer
rank writes data, `dl.notify`s a flag on the consumer, and the
consumer `dl.wait`s the flag before reading.

On TPU the same protocol is *one* operation: a remote DMA always
increments the destination's receive semaphore when the bytes land, so
`put == put-with-signal` and the consumer's wait is a semaphore wait.
This example: every rank puts a message into its right neighbor's
mailbox; the neighbor waits for delivery, then adds its rank to it.
"""

import functools
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])
from examples._bootstrap import make_mesh  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.experimental import pallas as pl  # noqa: E402
from jax.experimental.pallas import tpu as pltpu  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from triton_distributed_tpu.language import core as dl  # noqa: E402
from triton_distributed_tpu.ops import shard_map_op  # noqa: E402
from triton_distributed_tpu.utils.platform import (  # noqa: E402
    comm_compiler_params,
    default_interpret,
)


def kernel(axis, world, x_ref, o_ref, mailbox_ref, local_sem, send_sem,
           recv_sem):
    my = dl.rank(axis)                       # == libshmem my_pe()
    right = jax.lax.rem(my + 1, world)

    # Peers will DMA into our mailbox: barrier so nobody writes into a
    # buffer the previous program might still own (canonical pattern).
    dl.entry_barrier(axis, world)

    # One-sided put to the right neighbor. The returned descriptor's
    # recv side IS the notify: no separate flag write needed.
    dl.put_nbi(x_ref, mailbox_ref, send_sem, recv_sem,
               dl.peer_id(axis, right))

    # Consumer side: wait until the left neighbor's put landed
    # (== dl.wait on the flag), then it is safe to read the mailbox.
    dl.wait_recv(mailbox_ref, recv_sem)
    dl.wait_send(x_ref, send_sem)

    # HBM refs aren't directly addressable — stage through VMEM for
    # the compute (+= my), exactly like real kernels pipeline HBM.
    def finish(vscr):
        dl.local_copy(mailbox_ref, vscr, local_sem)
        vscr[...] = vscr[...] + my.astype(jnp.float32)
        dl.local_copy(vscr, o_ref, local_sem)

    pl.run_scoped(finish, pltpu.VMEM(x_ref.shape, jnp.float32))


def main():
    mesh = make_mesh()
    world = mesh.shape["tp"]

    def op(x):
        return pl.pallas_call(
            functools.partial(kernel, "tp", world),
            out_shape=(
                jax.ShapeDtypeStruct(x.shape, x.dtype),
                jax.ShapeDtypeStruct(x.shape, x.dtype),  # mailbox
            ),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=(pl.BlockSpec(memory_space=pl.ANY),) * 2,
            scratch_shapes=[
                pltpu.SemaphoreType.DMA(()),
                pltpu.SemaphoreType.DMA(()),
                pltpu.SemaphoreType.DMA(()),
            ],
            compiler_params=comm_compiler_params(63, world),
            interpret=default_interpret(None),
        )(x)[0]

    fn = shard_map_op(op, mesh, in_specs=P("tp", None),
                      out_specs=P("tp", None))
    # Rank r sends a buffer full of r; rank r therefore receives r-1
    # and adds its own rank: out[r] == (r - 1) % world + r.
    x = jnp.repeat(jnp.arange(world, dtype=jnp.float32)[:, None],
                   128, 1).repeat(8, 0)
    out = jax.jit(fn)(x).reshape(world, 8, 128)
    for r in range(world):
        expect = (r - 1) % world + r
        assert float(out[r, 0, 0]) == expect, (r, out[r, 0, 0])
    print(f"01_notify_wait OK on {world} devices "
          f"(rank r holds (r-1)%world + r)")


if __name__ == "__main__":
    main()
