"""05 — ReduceScatter: one-shot scatter-reduce vs flow-controlled ring.

Reference: `tutorials/05-intra-node-reduce-scatter.py`
(scatter-into-symmetric-buffers + ring reduce).

- SCATTER_REDUCE: every rank puts partial chunk c straight to chunk
  owner c (slot = sender's rank); owners sum `world` buffers with a
  pipelined VPU reduction. One hop.
- RING: running partial sums travel the ring; credit-based acks stop a
  fast left neighbor from overrunning the 2-slot staging buffer — the
  flow-control problem the reference solves with barrier arrays.
"""

import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])
from examples._bootstrap import make_mesh  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from triton_distributed_tpu.kernels.reduce_scatter import (  # noqa: E402
    ReduceScatterContext,
    ReduceScatterMethod,
    reduce_scatter,
)
from triton_distributed_tpu.ops import shard_map_op  # noqa: E402


def main():
    mesh = make_mesh()
    world = mesh.shape["tp"]
    # Every rank holds partials of the FULL (world*m, n) array.
    x = jax.random.normal(jax.random.key(0), (world, world * 8, 128))

    for method in (ReduceScatterMethod.SCATTER_REDUCE,
                   ReduceScatterMethod.RING):
        ctx = ReduceScatterContext(axis="tp", world_size=world,
                                   method=method)
        fn = shard_map_op(
            lambda xx, ctx=ctx: reduce_scatter(xx[0], ctx), mesh,
            in_specs=P("tp", None, None), out_specs=P("tp", None))
        out = jax.jit(fn)(x)
        ref = x.sum(0)
        assert float(jnp.abs(out - ref).max()) < 1e-4, method
        print(f"05_reduce_scatter {method.value:14s} OK")


if __name__ == "__main__":
    main()
