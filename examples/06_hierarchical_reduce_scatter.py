"""06 — Two-level ReduceScatter (reduce inside the slice FIRST).

Reference: `tutorials/06-inter-node-reduce-scatter.py` /
`reduce_scatter_2d_op`: partials meet over NVLink before anything
crosses IB, so the slow fabric carries 1/local_world of the bytes.

Same economics here: the Pallas intra-slice RS runs first, then a DCN
`psum_scatter` on the already-reduced 1/ici_size chunk.
"""

import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])
from examples._bootstrap import make_mesh  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from triton_distributed_tpu.kernels.hierarchical import (  # noqa: E402
    HierarchicalContext,
    reduce_scatter_2d,
)
from triton_distributed_tpu.ops import shard_map_op  # noqa: E402


def main():
    mesh = make_mesh(("dcn", "ici"), (2, 4))
    hctx = HierarchicalContext(ici_axis="ici", dcn_axis="dcn",
                               ici_size=4, dcn_size=2)
    world = 8
    x = jax.random.normal(jax.random.key(0), (world, world * 8, 128))

    fn = shard_map_op(
        lambda xx: reduce_scatter_2d(xx[0], hctx), mesh,
        in_specs=P(("dcn", "ici"), None, None),
        out_specs=P(("dcn", "ici"), None))
    out = jax.jit(fn)(x)
    assert float(jnp.abs(out - x.sum(0)).max()) < 1e-4
    print("06_hierarchical_reduce_scatter OK on a (2 x 4) mesh")


if __name__ == "__main__":
    main()
