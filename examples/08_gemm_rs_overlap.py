"""08 — Fused GEMM-ReduceScatter: the reverse overlap.

Reference: `tutorials/08-overlapping-gemm-reduce-scatter.py` /
`gemm_reduce_scatter.py`: the GEMM producer computes C tiles in
rank-swizzled order and scatters each straight to its owner while the
next tile computes.

TPU version: chunks go in (rank+1, rank+2, ..., rank) order — comm
starts after the FIRST chunk, and the own chunk (needing no transfer)
is computed last; each remote chunk matmuls into a double-buffered
staging slot and is put to its owner over ICI while the MXU moves on.
A final pipelined VPU reduction sums the received partials.
"""

import functools
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])
from examples._bootstrap import make_mesh  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from triton_distributed_tpu.kernels.gemm_reduce_scatter import (  # noqa: E402
    GEMMReduceScatterContext,
    gemm_rs,
)
from triton_distributed_tpu.kernels.matmul import MatmulConfig  # noqa: E402
from triton_distributed_tpu.ops import shard_map_op  # noqa: E402


def main():
    mesh = make_mesh()
    world = mesh.shape["tp"]
    mt, k_loc, n = world * 16, 64, 128
    a = jax.random.normal(jax.random.key(0), (mt, world * k_loc)) / 16
    b = jax.random.normal(jax.random.key(1), (world * k_loc, n)) / 16

    ctx = GEMMReduceScatterContext(axis="tp", world_size=world,
                                   method="fused",
                                   gemm=MatmulConfig(64, 128, 64))
    fn = shard_map_op(functools.partial(gemm_rs, ctx=ctx), mesh,
                      in_specs=(P(None, "tp"), P("tp", None)),
                      out_specs=P("tp", None))
    out = jax.jit(fn)(a, b)
    ref = a.astype(jnp.float32) @ b.astype(jnp.float32)
    assert float(jnp.abs(out - ref).max() / jnp.abs(ref).max()) < 1e-3
    print(f"08_gemm_rs fused OK  ({world} ranks, rank+1 swizzle)")


if __name__ == "__main__":
    main()
