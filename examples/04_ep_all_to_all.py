"""04 — EP AllToAll dispatch/combine (DeepSeek-style MoE inference).

Reference: `tutorials/04-deepseek-infer-all2all.py` and the
low-latency kernel (`low_latency_all_to_all.py`): tokens are grouped
by destination expert rank, pushed with ONE network traversal each
way, processed, and returned with a topk-weighted combine.

TPU notes: capacity-padded static shapes (XLA needs them), true counts
ride along as a narrow payload, and the recv-DMA semaphore is the
arrival signal (no call_count parity bookkeeping — semaphores are
allocated per call).
"""

import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])
from examples._bootstrap import make_mesh  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from triton_distributed_tpu.layers.ep_a2a_layer import (  # noqa: E402
    EPAll2AllLayer,
)
from triton_distributed_tpu.ops import shard_map_op  # noqa: E402


def main():
    mesh = make_mesh(("ep",))
    ep = mesh.shape["ep"]
    E, topk, n_loc, hidden, cap = 2 * ep, 2, 8, 64, 32
    layer = EPAll2AllLayer(axis="ep", ep_size=ep, num_experts=E,
                           topk=topk, max_tokens_per_rank=cap,
                           hidden=hidden)

    tokens = jax.random.normal(jax.random.key(0), (ep * n_loc, hidden))
    eids = jax.random.randint(jax.random.key(1), (ep * n_loc, topk), 0, E)
    w = jax.nn.softmax(jax.random.normal(jax.random.key(2),
                                         (ep * n_loc, topk)))

    def step(tok, eid, ww):
        # dispatch: tokens travel to their experts' ranks (1 traversal)
        recv, recv_expert, counts, plan = layer.dispatch(tok, eid)
        # "experts": identity here — a real MoE runs grouped GEMMs on
        # recv bucketed by recv_expert (see layers/moe_mlp.py)
        return layer.combine(recv, counts, plan, ww, eid)

    fn = shard_map_op(step, mesh, in_specs=(P("ep", None),) * 3,
                      out_specs=P("ep", None))
    out = jax.jit(fn)(tokens, eids, w)
    # identity experts -> combine = sum_k w_k * token = token
    ref = tokens * w.sum(1, keepdims=True)
    assert float(jnp.abs(out - ref).max()) < 1e-4
    print(f"04_ep_all_to_all OK ({ep} ranks, {E} experts, topk={topk})")


if __name__ == "__main__":
    main()
